#!/usr/bin/env bash
# Fleet autopilot: the closed control loop over the serving fleet
# (serve/autopilot.py).  The Autopilot consumes the same per-replica
# rollup records the observability plane aggregates plus the router's
# live queue, and actuates through the fleet's runtime-membership
# surface — every decision guarded by hysteresis holds, cooldowns, and
# bounded backoff, and every decision recorded with its inputs.
#
# Two arms, both wrapping tools/serve_fleet.py --autopilot:
#
# 1. GOOD ROLLOUT — 2 prewarmed replicas under sustained load; 2 s in,
#    a verified weight snapshot (same init seed, so tokens stay
#    byte-identical) is pushed as generation 1.  The autopilot spawns a
#    canary, shifts a hashed 25% traffic slice once it reports ready,
#    judges it over a fixed window (completions, SLO misses, windowed
#    TTFT ratio vs the stable generation), promotes, grows generation 1
#    to the old width, and drains generation 0 out (exit 47, ledger
#    intact).  Zero downtime: every request completes, and the flow
#    ledger attributes every completion to the generation that served
#    it.
#
# 2. CORRUPT CANARY — the snapshot payload is corrupted AFTER the
#    manifest commit (re-committed, so the autopilot's pre-spawn verify
#    passes — the TOCTOU shape).  The canary worker re-verifies against
#    its OWN load, fails, exits 44 (anomaly: terminal, no relaunch);
#    the autopilot rolls back automatically and generation 0 serves
#    every request, undisturbed.
set -euo pipefail

OUT=/tmp/nnpt_autopilot_example
rm -rf "$OUT" && mkdir -p "$OUT"

common=(--replicas 2 --vocab 64 --seq 64 --layers 2 --d-model 32
        --heads 4 --d-ff 64 --slots 4 --block-size 16
        --prefill-chunk 16 --step-sleep-ms 15 --slo-ms 8000
        --autopilot --min-replicas 2 --max-replicas 3 --json)

echo "== arm 1: good rollout (canary -> judge -> promote -> drain old) =="
python tools/serve_fleet.py "${common[@]}" \
    --prewarm --clients 8 --requests-per-client 60 \
    --rollout-after 2 --rollout-mode good \
    --canary-fraction 0.25 --canary-window 4 \
    --telemetry-dir "$OUT/good" > "$OUT/good.json"

echo "== arm 2: corrupt canary (verify-passes-then-load-fails -> rollback) =="
python tools/serve_fleet.py "${common[@]}" \
    --clients 4 --requests-per-client 40 \
    --rollout-after 2 --rollout-mode corrupt \
    --telemetry-dir "$OUT/corrupt" > "$OUT/corrupt.json"

python - <<'EOF'
import json

good = json.load(open("/tmp/nnpt_autopilot_example/good.json"))
acts = [d["action"] for d in good["decisions"]]
assert "canary_spawn" in acts and "canary_traffic" in acts, acts
assert "canary_promote" in acts and "rollout_complete" in acts, acts
assert "canary_rollback" not in acts, acts
per_gen = {int(k): v for k, v in
           good["per_generation_completed"].items()}
assert set(per_gen) == {0, 1} and sum(per_gen.values()) == \
    good["requests"], per_gen
done = [d for d in good["decisions"]
        if d["action"] == "rollout_complete"][0]
promote = [d for d in good["decisions"]
           if d["action"] == "canary_promote"][0]
print(f"rollout: promoted at t={promote['t']}s "
      f"(p50 ratio {promote['p50_ratio']}, "
      f"miss frac {promote['miss_frac']}), "
      f"complete at t={done['t']}s (wall {done['wall_s']}s)")
print(f"zero downtime: all {good['requests']} requests completed "
      f"({good['requeued']} drain handoffs requeued); "
      f"per-generation attribution {per_gen}")

bad = json.load(open("/tmp/nnpt_autopilot_example/corrupt.json"))
acts = [d["action"] for d in bad["decisions"]]
assert "canary_rollback" in acts, acts
assert "canary_promote" not in acts, acts
rb = [d for d in bad["decisions"]
      if d["action"] == "canary_rollback"][0]
assert "rc 44" in rb["reason"], rb
per_gen = {int(k): v for k, v in
           bad["per_generation_completed"].items()}
assert per_gen == {0: bad["requests"]}, per_gen
print(f"corrupt canary: rolled back at t={rb['t']}s "
      f"({rb['reason']}); generation 0 undisturbed "
      f"(all {bad['requests']} requests, "
      f"{bad['requeued']} requeued)")
EOF
echo "fleet autopilot example done"
