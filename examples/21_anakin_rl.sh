#!/usr/bin/env bash
# Anakin actor-learner RL on the training mesh (rl/, DESIGN.md §13):
# gridworld PPO end to end on CPU — environments sharded over the data
# axes, rollout + GAE + clipped-surrogate update fused into one jitted
# step.  The script proves two contracts:
#   1. LEARNING: the trained policy's return EMA beats the measured
#      random-policy baseline (the same program with --lr 0);
#   2. TRAJECTORY-EXACT RESUME: a run checkpointed mid-way and resumed
#      lands on the BITWISE-identical params of the uninterrupted run
#      (RLState round-trips env state, observations and PRNG keys).
set -euo pipefail
CKPT=$(mktemp -d)
CKPT2=$(mktemp -d)
LOGS=$(mktemp -d)
COMMON=(--workload rl --platform "${PLATFORM:-cpu}"
        --num_devices "${NUM_DEVICES:-8}"
        --rl_env gridworld --rl_envs 32 --rollout_steps 16
        --optimizer adam --seed 7)

echo "--- random-policy baseline (same program, lr 0) ---"
python -m neural_networks_parallel_training_with_mpi_tpu \
    "${COMMON[@]}" --lr 0 --rl_updates 10 2>&1 | tee "$LOGS/baseline.log"

echo "--- train 15 updates, checkpointing every 5 ---"
python -m neural_networks_parallel_training_with_mpi_tpu \
    "${COMMON[@]}" --lr 3e-3 --rl_updates 15 \
    --checkpoint_dir "$CKPT" --checkpoint_every 5 2>&1 \
    | tee "$LOGS/half.log"

echo "--- resume from the verified checkpoint to 30 updates ---"
python -m neural_networks_parallel_training_with_mpi_tpu \
    "${COMMON[@]}" --lr 3e-3 --rl_updates 30 \
    --checkpoint_dir "$CKPT" --resume 2>&1 | tee "$LOGS/resumed.log"

echo "--- uninterrupted 30 updates (the oracle trajectory) ---"
python -m neural_networks_parallel_training_with_mpi_tpu \
    "${COMMON[@]}" --lr 3e-3 --rl_updates 30 \
    --checkpoint_dir "$CKPT2" 2>&1 | tee "$LOGS/straight.log"

python - "$LOGS" <<'EOF'
import re
import sys

logs = sys.argv[1]


def parse(name):
    text = open(f"{logs}/{name}.log").read()
    m = re.search(r"rl: return [^ ]+ -> EMA ([0-9.eE+-]+|nan) over .*"
                  r"params sha256 ([0-9a-f]{64})", text)
    assert m, f"{name}.log carries no rl summary line"
    return float(m.group(1)), m.group(2)


baseline_ema, _ = parse("baseline")
trained_ema, straight_sha = parse("straight")
resumed_ema, resumed_sha = parse("resumed")
print(f"random-policy return EMA {baseline_ema:.3f} -> "
      f"trained {trained_ema:.3f}")
assert trained_ema > baseline_ema + 0.2, (
    f"PPO did not improve on the random baseline: "
    f"{trained_ema} vs {baseline_ema}")
print("return improved over the random-policy baseline")
assert resumed_sha == straight_sha, (
    f"resume diverged from the uninterrupted trajectory:\n"
    f"  resumed  {resumed_sha}\n  straight {straight_sha}")
print(f"resume trajectory-exact: params sha256 {straight_sha[:16]}... "
      "identical")
EOF

rm -rf "$CKPT" "$CKPT2" "$LOGS"
