#!/usr/bin/env bash
# Production-shaped serving: the continuous-batching scheduler (serve/)
# over the paged KV cache.  Ragged prompts arrive with per-request SLOs,
# the bounded queue admits them as slots+blocks free up, long prompts
# prefill in chunks INTERLEAVED with in-flight decode, and heterogeneous
# stream lengths share one block pool instead of each reserving max_len.
# Greedy results are token-identical to the single-stream generate()
# (pinned by tests/test_serve_paged.py); per-request TTFT/ITL print at
# the end — the numbers BENCH_SERVE.json sweeps against offered load.
# The same request set then re-runs with attn_impl='fused' (the Pallas
# paged-attention kernel, interpret mode on CPU) and must emit the SAME
# tokens — the dispatch seam is invisible to clients.
set -euo pipefail

python - <<'EOF'
from neural_networks_parallel_training_with_mpi_tpu.utils import platform as plat

plat.pin("cpu", num_devices=1)
import jax.numpy as jnp
import numpy as np

from neural_networks_parallel_training_with_mpi_tpu.models import (
    Transformer, TransformerConfig, generate,
)
from neural_networks_parallel_training_with_mpi_tpu.serve import (
    Scheduler, ServeConfig,
)
from neural_networks_parallel_training_with_mpi_tpu.utils import prng

model = Transformer(TransformerConfig(
    vocab_size=256, max_seq_len=128, n_layers=2, d_model=64, n_heads=4,
    d_ff=128))
params = model.init(prng.init_key(0))

# 8 streams max in the batched step; 33 blocks x 16 positions of KV pool
# shared by every stream (a dense slot server with this memory would
# hold FOUR 128-token streams; see BENCH_SERVE.json's capacity A/B).
# attn_impl toggles the attention dispatch: 'gathered' materializes
# pool[table]; 'fused' walks only allocated blocks in a Pallas kernel
cfg = dict(slots=8, num_blocks=33, block_size=16, prefill_chunk=32,
           queue_depth=16)
sched = Scheduler(model, params, ServeConfig(**cfg, attn_impl="gathered"))

# warmup: pay the (cached) prefill-bucket + decode-step compiles once,
# so the printed TTFT/ITL are steady-state serving numbers, not XLA
# compilation time
for plen in (3, 12, 24, 39):
    sched.submit(list(range(1, plen + 1)), 2)
sched.run_until_drained()

requests = [
    ([10, 20, 30], 24, 500.0),                  # short prompt, tight SLO
    (list(range(1, 40)), 16, None),             # 39-token prompt: chunked
    ([7, 8], 12, 1000.0),
    ([5, 9, 11, 13] * 6, 20, None),             # straddles block bounds
]
rids = {}
for prompt, n, slo in requests:
    rid = sched.submit(prompt, n, slo_ms=slo)
    assert rid is not None, "bounded queue rejected (raise queue_depth)"
    rids[rid] = (prompt, n)
print(f"queued {len(rids)} ragged requests "
      f"({sched.server.free_blocks} free KV blocks)")

order = sched.run_until_drained()
print(f"drained in {sched.tick_no} ticks, completion order {order}")

wants = {}
for rid, (prompt, n) in rids.items():
    got = sched.result(rid)
    want = [int(t) for t in np.asarray(
        generate(model, params, jnp.asarray([prompt], jnp.int32), n))[0]]
    assert got == want, (rid, got, want)
    wants[(tuple(prompt), n)] = want
    st = sched.stats(rid)
    print(f"req {rid}: prompt {len(prompt):>2} tok -> +{n:>2} tok   "
          f"TTFT {st.ttft_ms:7.1f} ms   ITL {st.itl_ms:5.1f} ms"
          + ("   (SLO met)" if st.slo_ms and not st.deadline_missed
             else ""))
sched.server.allocator.assert_drained()   # zero leaked blocks
sched.close()
print("paged continuous-batched tokens == single-stream generate() "
      "for all requests; block pool fully drained")

# same requests through the FUSED paged-attention kernel: the dispatch
# seam must not move a single token (checked against the SAME generate()
# references the gathered pass just verified — no second eager decode),
# and the attended-keys telemetry shows the work the kernel skips
fused = Scheduler(model, params, ServeConfig(**cfg, attn_impl="fused"))
fused_rids = {fused.submit(prompt, n, slo_ms=slo): (prompt, n)
              for prompt, n, slo in requests}
fused.run_until_drained()
for rid, (prompt, n) in fused_rids.items():
    got = fused.result(rid)
    assert got == wants[(tuple(prompt), n)], (rid, got)
ratio = fused.attended_keys / fused.padded_keys
print(f"fused kernel attended {fused.attended_keys} of "
      f"{fused.padded_keys} padded key positions "
      f"(ratio {ratio:.3f} — the skipped FLOPs)")
fused.server.allocator.assert_drained()
fused.close()
print("attn_impl=fused == attn_impl=gathered: token-identical end to end")
EOF
