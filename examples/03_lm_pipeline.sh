#!/usr/bin/env bash
# Tiny transformer LM across 4 data-parallel x 2 pipeline stages (GPipe
# microbatch schedule, ppermute activation ring), bfloat16 matmuls.
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 32 --nepochs 1 \
    --optimizer adam --lr 1e-3 --compute_dtype bfloat16 \
    --n_layers 4 --dp 4 --pp 2
