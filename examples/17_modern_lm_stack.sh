#!/usr/bin/env bash
# The modern-LM stack in one CLI run: RoPE rotary positions (no position
# parameters), SwiGLU gated FFN, and grouped-query attention (half the
# KV heads), trained on the virtual mesh, checkpointed, then decoded
# with every serving lever stacked — int8 weights + int8 KV cache.
# The reference's model is a 13-parameter MLP (dataParallelTraining_NN_MPI.py:41-45);
# this is the "don't stop at parity" model family.
set -euo pipefail
CKPT="$(mktemp -d)"
trap 'rm -rf "$CKPT"' EXIT

python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 32 --nepochs 1 \
    --optimizer adam --lr 1e-3 --seq_len 32 \
    --pos_encoding rope --ffn_activation swiglu \
    --n_heads 4 --n_kv_heads 2 \
    --checkpoint_dir "$CKPT"

echo "--- decode the RoPE x SwiGLU x GQA checkpoint, int8 weights + int8 KV"
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-1}" \
    --dataset lm --seq_len 32 \
    --pos_encoding rope --ffn_activation swiglu \
    --n_heads 4 --n_kv_heads 2 \
    --checkpoint_dir "$CKPT" \
    --generate "10,20,30" --max_new_tokens 8 \
    --quantize int8 --quantize_skip head --kv_quant int8
