#!/usr/bin/env bash
# Native tensor-parallel serving: train a tiny LM on the 3-D
# DP x SP x TP mesh (Megatron matmuls + ring attention), checkpoint it,
# then decode the SP x TP checkpoint in its NATIVE layout with
# models.generate_tp — Megatron-sharded blocks, head-sharded KV caches,
# vocab-parallel Gumbel-max sampling; no host gather, no dense copy.
# (The CLI's --generate also decodes the same checkpoint by reconciling
# the layout to dense — shown last for comparison.)
set -euo pipefail
CKPT="$(mktemp -d)"
trap 'rm -rf "$CKPT"' EXIT

python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 32 --nepochs 1 \
    --optimizer adam --lr 1e-3 --seq_len 32 \
    --dp 2 --sp 2 --tp 2 --checkpoint_dir "$CKPT"

python - "$CKPT" <<'EOF'
import sys

import numpy as np

from neural_networks_parallel_training_with_mpi_tpu.utils import platform as plat

plat.pin("cpu", num_devices=8)
import jax
import jax.numpy as jnp

from neural_networks_parallel_training_with_mpi_tpu.config import MeshConfig
from neural_networks_parallel_training_with_mpi_tpu.models import (
    Transformer, TransformerConfig, generate_tp,
)
from neural_networks_parallel_training_with_mpi_tpu.parallel import mesh as mesh_lib
from neural_networks_parallel_training_with_mpi_tpu.utils import checkpoint as ckpt

restored = ckpt.restore(sys.argv[1], template=None)
# must mirror the training run's model config (CLI defaults for
# --dataset lm at --seq_len 32: max_seq_len = max(seq_len, 512))
model = Transformer(TransformerConfig(vocab_size=256, max_seq_len=512,
                                      n_layers=2, d_model=128, n_heads=4,
                                      d_ff=512))
mesh = mesh_lib.make_mesh(MeshConfig(data=2, tensor=2),
                          devices=np.asarray(jax.devices()[:4]))
prompt = jnp.asarray([[10, 20, 30], [40, 50, 60]], jnp.int32)
out = generate_tp(model, restored.params, prompt, mesh, max_new_tokens=8)
print("native TP decode:", np.asarray(out).tolist())
EOF

# the CLI path reconciles the same checkpoint to the dense layout:
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --seq_len 32 --checkpoint_dir "$CKPT" \
    --generate "10,20,30" --max_new_tokens 8
