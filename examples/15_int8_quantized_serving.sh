#!/usr/bin/env bash
# Int8 serving, both halves (ops.quant + ops.qmm): train a tiny byte-LM,
# checkpoint it, then decode the SAME checkpoint four ways —
#   1. full precision,
#   2. --quantize int8 --kv_quant int8 (weights-only PTQ + int8 KV
#      cache: the BANDWIDTH half — int8 kernels + one f32 scale per
#      output channel, matmul still in the compute dtype),
#   3. --quantize int8 alone (the parity baseline for arm 4), and
#   4. --quantize int8 --matmul_dtype int8 (the COMPUTE half: a true
#      int8 activation x int8 weight dot with dynamic per-token
#      activation scales, int8 x int8 -> int32 on the MXU, both scales
#      folded on the output tile — ops/qmm.py, DESIGN.md §14).
# Arms 3 and 4 must agree on most greedy tokens (asserted below at the
# 60% tolerance DESIGN.md §14 states — on a trained model the per-token
# activation rounding can flip near-tie argmaxes, which then cascade;
# the random-init exact pin lives in tests/test_qmm.py and the bench
# prompts' exactness boolean in BENCH_QUANT.json).  The int8-compute
# arm is the one that also runs the arithmetic at int8 MXU rates on
# real hardware.  The reference has no inference path at all (its eval
# blocks are dead code, dataParallelTraining_NN_MPI.py:213-236).
set -euo pipefail
CKPT="$(mktemp -d)"
trap 'rm -rf "$CKPT"' EXIT

python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 32 --nepochs 1 \
    --optimizer adam --lr 1e-3 --seq_len 32 --checkpoint_dir "$CKPT"

echo "--- full-precision decode"
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-1}" \
    --dataset lm --seq_len 32 --checkpoint_dir "$CKPT" \
    --generate "10,20,30" --max_new_tokens 8

echo "--- int8 weights + int8 KV cache (same checkpoint; --quantize_skip
---     head keeps the logit projection exact, --kv_quant int8 stores the
---     KV cache as int8 with per-position scales)"
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-1}" \
    --dataset lm --seq_len 32 --checkpoint_dir "$CKPT" \
    --generate "10,20,30" --max_new_tokens 8 \
    --quantize int8 --quantize_skip head --kv_quant int8

echo "--- int8 PTQ decode (parity baseline for the int8-compute arm)"
PTQ_TOKENS=$(python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-1}" \
    --dataset lm --seq_len 32 --checkpoint_dir "$CKPT" \
    --generate "10,20,30" --max_new_tokens 8 \
    --quantize int8 --quantize_skip head | tail -1)
echo "$PTQ_TOKENS"

echo "--- int8 COMPUTE decode (same PTQ weights; --matmul_dtype int8 runs
---     a true int8 activation x weight dot — ops/qmm.py — instead of
---     dequantizing into the compute-dtype matmul)"
QDOT_TOKENS=$(python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-1}" \
    --dataset lm --seq_len 32 --checkpoint_dir "$CKPT" \
    --generate "10,20,30" --max_new_tokens 8 \
    --quantize int8 --quantize_skip head \
    --matmul_dtype int8 | tail -1)
echo "$QDOT_TOKENS"

python - "$PTQ_TOKENS" "$QDOT_TOKENS" <<'PY'
import sys
a = [int(t) for t in sys.argv[1].split(",")]
b = [int(t) for t in sys.argv[2].split(",")]
assert len(a) == len(b) and a[:3] == b[:3], (a, b)  # prompt echo intact
agree = sum(x == y for x, y in zip(a[3:], b[3:])) / len(a[3:])
print(f"int8-compute vs PTQ greedy-token agreement: {agree:.0%}")
assert agree >= 0.6, f"agreement {agree:.0%} below the 60% tolerance"
PY
