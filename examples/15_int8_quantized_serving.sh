#!/usr/bin/env bash
# Weights-only int8 serving (ops.quant): train a tiny byte-LM, checkpoint
# it, then decode the SAME checkpoint twice — full precision and with
# --quantize int8 (dense kernels stored int8 + one f32 scale per output
# channel; the matmul stays bf16 on the MXU with the scale folded into
# the output tile).  Autoregressive decode is bandwidth-bound streaming
# the weights once per token, so int8 halves the HBM bytes per token on
# chip; numerics parity is pinned by tests/test_quant.py.  The reference
# has no inference path at all (its eval blocks are dead code,
# dataParallelTraining_NN_MPI.py:213-236) — this is a TPU-serving
# extension.
set -euo pipefail
CKPT="$(mktemp -d)"
trap 'rm -rf "$CKPT"' EXIT

python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --dataset lm --no-full-batch --batch_size 32 --nepochs 1 \
    --optimizer adam --lr 1e-3 --seq_len 32 --checkpoint_dir "$CKPT"

echo "--- full-precision decode"
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-1}" \
    --dataset lm --seq_len 32 --checkpoint_dir "$CKPT" \
    --generate "10,20,30" --max_new_tokens 8

echo "--- int8 weights + int8 KV cache (same checkpoint; --quantize_skip
---     head keeps the logit projection exact, --kv_quant int8 stores the
---     KV cache as int8 with per-position scales)"
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-1}" \
    --dataset lm --seq_len 32 --checkpoint_dir "$CKPT" \
    --generate "10,20,30" --max_new_tokens 8 \
    --quantize int8 --quantize_skip head --kv_quant int8
