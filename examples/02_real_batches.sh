#!/usr/bin/env bash
# Real minibatches (the reference parses --batch_size but ignores it — bug
# B1), cosine lr schedule with warmup, global-norm clipping, a held-out
# validation split evaluated every epoch, and structured metrics.
set -euo pipefail
python -m neural_networks_parallel_training_with_mpi_tpu \
    --platform "${PLATFORM:-cpu}" --num_devices "${NUM_DEVICES:-8}" \
    --n_samples 10000 --no-full-batch --batch_size 256 --nepochs 10 \
    --lr 0.01 --lr_schedule cosine --warmup_steps 50 --grad_clip 1.0 \
    --val_fraction 0.1 --eval_every 1 --metrics_jsonl /tmp/metrics.jsonl
